"""coll/han (2-level sub-communicator composition) and coll/xhc
(n-level ladder) hierarchical collectives."""
import json

import numpy as np
import pytest

import ompi_tpu as MPI
from ompi_tpu.coll import han as han_mod
from ompi_tpu.coll.han import HanModule
from ompi_tpu.coll.xhc import XhcModule, build_levels


from ompi_tpu.mca import var


@pytest.fixture()
def _vars():
    """Set MCA vars programmatically (env resolution happens once, at
    registration) and restore afterwards."""
    saved = {}

    def set_(name, value):
        saved.setdefault(name, var.var_get(name))
        var.var_set(name, value)

    yield set_
    for name, value in saved.items():
        var.var_set(name, value)


@pytest.fixture()
def han_world(world, _vars):
    """A dup of COMM_WORLD with a synthetic 2-node hierarchy (low
    groups of 4 — the ICI/DCN boundary stand-in) and han priority
    raised above every data-plane component."""
    _vars("coll_han_priority", 80)
    _vars("coll_han_split", 4)
    han_mod._reset_rules_for_tests()
    c = world.dup()
    yield c
    han_mod._reset_rules_for_tests()


def test_han_wins_with_hierarchy(han_world):
    assert han_world._coll_winners["allreduce"] == "han"
    assert isinstance(han_world.c_coll["allreduce"], HanModule)


def test_han_not_selected_without_hierarchy(world, _vars):
    _vars("coll_han_priority", 80)
    _vars("coll_han_split", 0)
    c = world.dup()          # flat CPU mesh: one process = no hierarchy
    assert c._coll_winners["allreduce"] != "han"


def test_han_allreduce(han_world, rng):
    n = han_world.size
    x = rng.standard_normal((n, 300)).astype(np.float32)  # > 256 B: hier
    out = np.asarray(han_world.allreduce(han_world.stack(list(x)),
                                         MPI.SUM))
    for r in range(n):
        np.testing.assert_allclose(out[r], x.sum(0), rtol=1e-4)
    # the tiers actually exist and were selected independently
    m = han_world.c_coll["allreduce"]
    assert len(m.h.low) == 2 and m.h.up.size == 2
    assert all(getattr(c, "_han_inner", False)
               for c in m.h.low + [m.h.up])


def test_han_allreduce_max(han_world, rng):
    n = han_world.size
    x = rng.standard_normal((n, 130)).astype(np.float32)
    out = np.asarray(han_world.allreduce(han_world.stack(list(x)),
                                         MPI.MAX))
    np.testing.assert_allclose(out[0], x.max(0), rtol=1e-5)


def test_han_bcast_reduce(han_world, rng):
    n = han_world.size
    x = rng.standard_normal((n, 65)).astype(np.float32)
    buf = han_world.stack(list(x))
    out = np.asarray(han_world.bcast(buf, root=5))
    for r in range(n):
        np.testing.assert_allclose(out[r], x[5], rtol=1e-6)
    red = np.asarray(han_world.reduce(buf, MPI.SUM, root=6))
    np.testing.assert_allclose(red[6], x.sum(0), rtol=1e-4)


def test_han_allgather(han_world, rng):
    n = han_world.size
    x = rng.standard_normal((n, 7)).astype(np.float32)
    out = np.asarray(han_world.allgather(han_world.stack(list(x))))
    for r in range(n):
        np.testing.assert_allclose(out[r], x, rtol=1e-6)


def test_han_barrier(han_world):
    han_world.barrier()      # composes low/up barriers without error


def test_han_small_message_goes_flat(han_world, rng):
    """Default dynamic table: <= 256 B skips the hierarchy (level
    latency dominates) and delegates to the next component."""
    n = han_world.size
    x = rng.standard_normal((n, 4)).astype(np.float32)   # 16 B
    m = han_world.c_coll["allreduce"]
    assert m._strategy("allreduce", 16) == "flat"
    out = np.asarray(han_world.allreduce(han_world.stack(list(x)),
                                         MPI.SUM))
    np.testing.assert_allclose(out[0], x.sum(0), rtol=1e-4)


def test_han_dynamic_rules_file(world, _vars, tmp_path, rng):
    rules = {"allreduce": [{"max_bytes": 10**9, "algorithm": "flat"}]}
    path = tmp_path / "han_rules.json"
    path.write_text(json.dumps(rules))
    _vars("coll_han_priority", 80)
    _vars("coll_han_split", 4)
    _vars("coll_han_dynamic_rules", str(path))
    han_mod._reset_rules_for_tests()
    c = world.dup()
    m = c.c_coll["allreduce"]
    assert m._strategy("allreduce", 1 << 20) == "flat"
    x = rng.standard_normal((c.size, 1000)).astype(np.float32)
    out = np.asarray(c.allreduce(c.stack(list(x)), MPI.SUM))
    np.testing.assert_allclose(out[0], x.sum(0), rtol=1e-4)
    han_mod._reset_rules_for_tests()


# ---------------------------------------------------------------------
def test_build_levels():
    lv = build_levels(8, [2, 2])
    assert lv[0] == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert lv[1] == [[0, 2], [4, 6]]
    assert lv[2] == [[0, 4]]
    assert build_levels(4, [4]) == [[[0, 1, 2, 3]]]
    assert build_levels(1, [2]) == []


@pytest.fixture()
def xhc_world(world, _vars):
    _vars("coll_xhc_priority", 80)
    _vars("coll_xhc_levels", "2,2")
    return world.dup()


def test_xhc_wins_and_ladder(xhc_world):
    assert xhc_world._coll_winners["allreduce"] == "xhc"
    m = xhc_world.c_coll["allreduce"]
    assert isinstance(m, XhcModule)
    assert len(m.levels) == 3    # pairs, pairs-of-leaders, top


def test_xhc_allreduce_ops(xhc_world, rng):
    n = xhc_world.size
    x = rng.standard_normal((n, 50)).astype(np.float32)
    buf = xhc_world.stack(list(x))
    for op, ref in ((MPI.SUM, x.sum(0)), (MPI.MAX, x.max(0)),
                    (MPI.MIN, x.min(0))):
        out = np.asarray(xhc_world.allreduce(buf, op))
        for r in range(n):
            np.testing.assert_allclose(out[r], ref, rtol=1e-4)


def test_xhc_bcast_reduce_barrier(xhc_world, rng):
    n = xhc_world.size
    x = rng.standard_normal((n, 9)).astype(np.float32)
    buf = xhc_world.stack(list(x))
    out = np.asarray(xhc_world.bcast(buf, root=3))
    np.testing.assert_allclose(out[7], x[3], rtol=1e-6)
    red = np.asarray(xhc_world.reduce(buf, MPI.SUM, root=1))
    np.testing.assert_allclose(red[1], x.sum(0), rtol=1e-4)
    xhc_world.barrier()


@pytest.fixture()
def xhc_auto_world(world, _vars):
    """xhc preferred but NO explicit level list — the ladder must come
    from synthesized locality (VERDICT r4 next #10)."""
    _vars("coll_xhc_priority", 80)
    return world.dup()


def test_xhc_ladder_without_levels_var(xhc_auto_world, rng):
    """The hwloc-depth walk: with coll_xhc_levels UNSET on this flat
    8-device CPU mesh, xhc still builds a >= 2-level ladder (OS
    topology when the host has depth, labeled synthetic factorization
    otherwise) and the collectives stay correct."""
    w = xhc_auto_world
    assert w._coll_winners["allreduce"] == "xhc"
    m = w.c_coll["allreduce"]
    assert isinstance(m, XhcModule)
    assert len(m.levels) >= 2, m.levels
    assert getattr(m, "level_basis", "") in (
        "os-topology", "synthetic-mesh", "device-locality")
    n = w.size
    x = rng.standard_normal((n, 17)).astype(np.float32)
    out = np.asarray(w.allreduce(w.stack(list(x)), MPI.SUM))
    np.testing.assert_allclose(out[0], x.sum(0), rtol=1e-4)


def test_ladder_sizes_provenance():
    from ompi_tpu.utils.locality import ladder_sizes
    sizes, basis = ladder_sizes(8)
    assert sizes and basis in ("os-topology", "synthetic-mesh")
    assert ladder_sizes(2)[0] is None          # trivial stays trivial
    assert ladder_sizes(7)[0] is None          # prime, single level
