"""Zero-copy shared-memory data plane (btl/shmseg): segment-pool unit
coverage, the byte-identical off-gate, reclaim discipline (finalizer ->
segfree ctl, FT pool reclaim, close/unlink), and the live multi-process
parity drives (docs/LARGEMSG.md).

The fast tests exercise SegPlane directly — two planes sharing a dict
KV stand in for two ranks on one host — without spawning processes.
The ``test_shmfold_*_matches_ring`` pair (the parity contract
tools/checkparity.py enforces for every coll/decision SHM_FOLDS
schedule) and the composition matrix (depth sweep, compression,
rails=2, dropped-peer FT) launch tests/perrank_programs/p42_shmseg.py
as a real multi-process job and carry the ``slow`` marker."""
import gc
import glob
import os
import subprocess
import sys

import numpy as np
import pytest

from ompi_tpu.btl import shmseg
from ompi_tpu.btl.sm import _SHM_DIR
from ompi_tpu.mca import var

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_MPIRUN = os.path.join(_REPO, "ompi_tpu", "tools", "mpirun.py")
_P42 = os.path.join(_REPO, "tests", "perrank_programs",
                    "p42_shmseg.py")


@pytest.fixture()
def _zc_env():
    """Zero-copy on with a low threshold; restore every knob after."""
    keys = {"mpi_base_shm_zerocopy": False,
            "mpi_base_shm_seg_min_bytes": 256 << 10,
            "mpi_base_shm_seg_bytes": 32 << 20,
            "mpi_base_shm_seg_count": 4}
    saved = {k: var.var_get(k, d) for k, d in keys.items()}
    var.var_set("mpi_base_shm_zerocopy", True)
    var.var_set("mpi_base_shm_seg_min_bytes", 1 << 16)
    yield
    for k, v in saved.items():
        var.var_set(k, v)


def _two_planes(ctl_log=None):
    kv = {}

    def ctl(owner, header):
        if ctl_log is not None:
            ctl_log.append((owner, dict(header)))
    a = shmseg.SegPlane(0, kv.__setitem__, kv.get, ctl_send=ctl)
    b = shmseg.SegPlane(1, kv.__setitem__, kv.get, ctl_send=ctl)
    return a, b


def test_pack_adopt_roundtrip_and_slot_reclaim(_zc_env):
    """pack -> adopt round-trips bits; dropping the adopted array's
    last reference fires the finalizer, whose segfree ctl releases the
    owner's slot."""
    log = []
    a, b = _two_planes(log)
    try:
        x = np.random.default_rng(0).normal(size=1 << 16) \
            .astype(np.float64)
        desc = a.pack(1, memoryview(x).cast("B"))
        assert desc is not None and desc["o"] == 0
        got = b.adopt(desc, {"dtype": x.dtype.str, "shape": x.shape})
        assert np.array_equal(got, x)
        assert got.flags.writeable     # decode_payload semantics
        del got
        gc.collect()
        assert log and log[-1][0] == 0 \
            and log[-1][1]["ctl"] == "segfree"
        a.release(log[-1][1]["peer"], log[-1][1]["i"])
        # the slot is free again: pool never runs dry on recycled use
        for _ in range(a.slot_count):
            d = a.pack(1, b"z" * (1 << 16))
            assert d is not None
            a.release(1, d["i"])
    finally:
        a.close()
        b.close()


def test_pool_dry_falls_back_then_recovers(_zc_env):
    """Every slot pinned -> pack returns None (the caller's ring
    fallback) and counts the fallback pvar; a release un-dries it."""
    a, b = _two_planes()
    try:
        held = [a.pack(1, b"x" * (1 << 16)) for _ in range(a.slot_count)]
        assert all(d is not None for d in held)
        n0 = shmseg.stats["no_slot"]
        assert a.pack(1, b"y" * (1 << 16)) is None
        assert shmseg.stats["no_slot"] == n0 + 1
        a.release(1, held[0]["i"])
        assert a.pack(1, b"y" * (1 << 16)) is not None
    finally:
        a.close()
        b.close()


def test_peer_failed_reclaims_pool(_zc_env):
    """FT reclaim: a dead peer can never send segfree — the whole pool
    comes back at once."""
    a, b = _two_planes()
    try:
        for _ in range(a.slot_count):
            assert a.pack(1, b"x" * (1 << 16)) is not None
        assert a.pack(1, b"x" * (1 << 16)) is None
        a.peer_failed(1)
        assert a.pack(1, b"x" * (1 << 16)) is not None
    finally:
        a.close()
        b.close()


def test_view_matches_pack_bytes(_zc_env):
    """The transient pipeseg view reads exactly the packed bytes."""
    a, b = _two_planes()
    try:
        payload = os.urandom(1 << 17)
        desc = a.pack(1, payload)
        mv = b.view(desc)
        assert bytes(mv) == payload
        mv.release()
    finally:
        a.close()
        b.close()


def test_fold_workspace_shared_and_unlinked(_zc_env):
    """coll_segment/coll_attach share one mapping; close unlinks every
    created file (the shutdown reclaim the launcher sweep backs up)."""
    a, b = _two_planes()
    try:
        wa = a.coll_segment("t0")
        wb = b.coll_attach("t0", 0)
        wa.buf[0:8] = b"deadbeef"
        assert bytes(wb.buf[0:8]) == b"deadbeef"
        wb.buf[0:4] = b"feed"            # fold writes go both ways
        assert bytes(wa.buf[0:4]) == b"feed"
    finally:
        a.close()
        b.close()
    assert not glob.glob(os.path.join(_SHM_DIR, "otpuseg_*")), \
        "SegPlane.close leaked /dev/shm segment files"


def test_off_gate_and_loopback_decline(_zc_env):
    """maybe_send_zerocopy never touches the wire when the gate is off,
    below threshold, for object dtypes, or on loopback — the fallback
    path is the unchanged (byte-identical) serial path."""
    from ompi_tpu.pml.perrank import PerRankEngine, Router

    kv = {}
    router = Router(0, 1, kv.__setitem__, kv.__getitem__)
    try:
        class _C:
            cid = "zc0"
            size = 2

            def rank(self):
                return 0

            def world_rank_of(self, r):
                return 0                 # loopback: every dest is me
        eng = PerRankEngine(_C(), router)
        big = np.arange(1 << 18, dtype=np.float32)
        # loopback declines even with the gate on
        assert shmseg.maybe_send_zerocopy(eng, big, 1, 5, False) is None
        var.var_set("mpi_base_shm_zerocopy", False)
        assert shmseg.maybe_send_zerocopy(eng, big, 1, 5, False) is None
        var.var_set("mpi_base_shm_zerocopy", True)
        small = np.arange(8, dtype=np.float32)
        assert shmseg.maybe_send_zerocopy(eng, small, 1, 5, False) \
            is None
        objs = np.array([{"k": 1}, None], dtype=object)
        assert shmseg.maybe_send_zerocopy(eng, objs, 1, 5, False) \
            is None
        # and the serial path still round-trips with no segment files
        eng.send(big, 1, tag=5)
        got, _ = eng.recv(source=0, tag=5, timeout=30)
        assert np.array_equal(np.asarray(got), big)
        assert not glob.glob(os.path.join(_SHM_DIR, "otpuseg_*"))
    finally:
        router.close()


def test_decision_rows_gate_on_var(_zc_env):
    """The shm_fold rows appear in the decision table only while the
    gate is on (off = byte-identical ring dispatch)."""
    from ompi_tpu.coll import decision
    rules = decision.shm_rules()
    assert decision._match(rules["allreduce"], 2, 1 << 20) == "shm_fold"
    assert decision._match(rules["allreduce"], 1, 1 << 20) != "shm_fold"
    assert "shm_fold" in str(decision.decision_table(2)["allreduce"])
    var.var_set("mpi_base_shm_zerocopy", False)
    assert decision.shm_rules() == {}
    assert "shm_fold" not in str(decision.decision_table(2)["allreduce"])


def _run_p42(extra_env=None, n=2):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["OMPI_TPU_MCA_mpi_base_shm_zerocopy"] = "1"
    env.update(extra_env or {})
    cmd = [sys.executable, _MPIRUN, "--per-rank", "-n", str(n),
           "--timeout", "150", _P42]
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=200, cwd=_REPO)


def _assert_ok(res, n=2):
    assert res.returncode == 0, \
        f"rc={res.returncode}\n--- out\n{res.stdout}\n--- err\n" \
        f"{res.stderr[-4000:]}"
    assert res.stdout.count("OK p42_shmseg") == n, res.stdout
    assert not glob.glob(os.path.join(_SHM_DIR, "otpuseg_*")), \
        "job left orphaned /dev/shm segment files"


@pytest.mark.slow
def test_shmfold_allreduce_matches_ring():
    """2 real ranks: in-segment fold result equals the ring schedules,
    pvar-asserted adoption + fold inside the program (the checkparity
    pair for decision.SHM_FOLDS['allreduce'])."""
    _assert_ok(_run_p42())


@pytest.mark.slow
def test_shm_zerocopy_pipeline_depth_sweep():
    """shm-zerocopy x pipeline: slots smaller than the payload, so the
    rail segments pack slot by slot, across pipeline depths."""
    for depth in ("1", "4"):
        res = _run_p42({
            "P42_MODE": "pipe",
            "OMPI_TPU_MCA_mpi_base_shm_seg_bytes": str(1 << 20),
            "OMPI_TPU_MCA_mpi_base_pipeline_depth": depth})
        _assert_ok(res)


@pytest.mark.slow
def test_shm_zerocopy_compression_composition():
    """shm-zerocopy x compression: the compressed allreduce keeps its
    claim (the fold yields) and results stay correct."""
    res = _run_p42({"OMPI_TPU_MCA_mpi_base_compress": "1",
                    "OMPI_TPU_MCA_mpi_base_compress_min_bytes":
                        str(1 << 20)})
    _assert_ok(res)


@pytest.mark.slow
def test_shm_zerocopy_rails2_composition():
    """shm-zerocopy x multi-rail: rail-striped segments ride shared
    slots with both rails carrying traffic."""
    res = _run_p42({
        "P42_MODE": "pipe",
        "OMPI_TPU_MCA_mpi_base_shm_seg_bytes": str(1 << 20),
        "OMPI_TPU_MCA_mpi_base_btl_rails": "2"})
    _assert_ok(res)


@pytest.mark.slow
def test_shm_zerocopy_ft_drop_parity():
    """shm-zerocopy x dropped-peer FT: the drop-injection recovery
    drill (p35) passes unchanged with the segment plane armed, and no
    segment files leak."""
    p35 = os.path.join(_REPO, "tests", "perrank_programs",
                       "p35_ftdrop.py")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["OMPI_TPU_MCA_mpi_base_shm_zerocopy"] = "1"
    cmd = [sys.executable, _MPIRUN, "--per-rank", "-n", "2",
           "--timeout", "150", p35]
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                         timeout=200, cwd=_REPO)
    assert res.returncode == 0, \
        f"rc={res.returncode}\n--- out\n{res.stdout}\n--- err\n" \
        f"{res.stderr[-4000:]}"
    assert not glob.glob(os.path.join(_SHM_DIR, "otpuseg_*")), \
        "FT drill left orphaned /dev/shm segment files"
