"""coll/compressed — the quantized collective component on the 8-rank
CPU mesh: selection, uncompressed-equivalence (the checkparity-audited
pairs), byte-pvar accounting (<= 0.3x on the wire), the off-path
bit-identity contract, dtype/op/threshold gating, and the effective
decision-table exposure (api/tool.decision_table)."""
import numpy as np
import pytest

import ompi_tpu as MPI
from ompi_tpu.mca import pvar, var

MB4_ELEMS = 1 << 20                  # 4 MB of f32 per rank


@pytest.fixture()
def compress_world(world):
    """A communicator whose vtable was selected with compression ON
    (the han/xhc fixture idiom: enable, then dup so selection sees
    it). The threshold is dropped to 256 KiB so the smaller equivalence
    payloads engage too; the 4 MB acceptance test overrides nothing."""
    var.var_set("mpi_base_compress", True)
    var.var_set("mpi_base_compress_min_bytes", 256 << 10)
    c = world.dup()
    try:
        yield c
    finally:
        c.free()
        var.var_set("mpi_base_compress_min_bytes", 4 << 20)
        var.var_set("mpi_base_compress", False)


def _bytes():
    return (pvar.pvar_read("compress_bytes_in"),
            pvar.pvar_read("compress_bytes_out"))


def test_compressed_component_selected_only_when_enabled(world):
    assert world._coll_winners["allreduce"] != "compressed"
    var.var_set("mpi_base_compress", True)
    try:
        c = world.dup()
        assert c._coll_winners["allreduce"] == "compressed"
        assert c._coll_winners["allgather"] == "compressed"
        assert c._coll_winners["reduce_scatter_block"] == "compressed"
        # everything else backfills from the next-priority providers
        assert c._coll_winners["bcast"] != "compressed"
        assert c._coll_winners["barrier"] != "compressed"
        c.free()
    finally:
        var.var_set("mpi_base_compress", False)


def test_compressed_allreduce_4mb_within_bound_and_wire_budget(
        compress_world, rng):
    """The acceptance row: a >= 4 MB fp32 allreduce through the
    compressed path is correct within the documented error model,
    moves <= 0.3x the baseline bytes (pvar-asserted), and returns the
    SAME array on every rank."""
    c = compress_world
    n = c.size
    host = rng.normal(size=(n, MB4_ELEMS)).astype(np.float32)
    x = c.put(host)
    ref = host.sum(axis=0, dtype=np.float64)

    bi0, bo0 = _bytes()
    y = np.asarray(c.allreduce(x, MPI.SUM))
    bi1, bo1 = _bytes()
    assert bi1 > bi0, "compressed path never engaged"
    ratio = (bo1 - bo0) / (bi1 - bi0)
    assert ratio <= 0.3, f"wire ratio {ratio}"

    # error model: one int8 requant per reduce-scatter hop (n-1 hops
    # of partial sums) + one for the broadcast codes. Bound per
    # element by hops * blockmax/254 with blockmax <= max|partial|;
    # assert the measured error against a loose 2% of the result scale
    # (the documented envelope for n=8 gaussian payloads).
    err = np.abs(y[0].astype(np.float64) - ref).max()
    scale = np.abs(ref).max()
    assert err <= 0.02 * scale, f"err {err} vs scale {scale}"
    for r in range(1, n):
        assert np.array_equal(y[0], y[r]), "ranks diverged"


def test_compressed_allreduce_matches_uncompressed(compress_world,
                                                   world, rng):
    """Parity pair (tools/checkparity): same payload through the
    compressed comm and the plain world agrees within the codec
    bound."""
    n = world.size
    host = rng.normal(size=(n, 1 << 17)).astype(np.float32)  # 512 KiB
    ref = np.asarray(world.allreduce(world.put(host), MPI.SUM))
    out = np.asarray(compress_world.allreduce(
        compress_world.put(host), MPI.SUM))
    assert out.shape == ref.shape and out.dtype == ref.dtype
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() <= 0.02 * scale


def test_compressed_allgather_matches_uncompressed(compress_world,
                                                   world, rng):
    n = world.size
    host = rng.normal(size=(n, 1 << 17)).astype(np.float32)
    ref = np.asarray(world.allgather(world.put(host)))
    bi0, bo0 = _bytes()
    out = np.asarray(compress_world.allgather(compress_world.put(host)))
    bi1, bo1 = _bytes()
    assert bi1 > bi0
    assert (bo1 - bo0) / (bi1 - bi0) <= 0.3
    assert out.shape == ref.shape
    # allgather quantizes each contribution exactly once
    scale = np.abs(host).max()
    assert np.abs(out - ref).max() <= scale / 64
    for r in range(1, n):
        assert np.array_equal(out[0], out[r])


def test_compressed_reduce_scatter_block_matches_uncompressed(
        compress_world, world, rng):
    n = world.size
    host = rng.normal(size=(n, n, 1 << 16)).astype(np.float32)
    ref = np.asarray(world.reduce_scatter_block(world.put(host),
                                                MPI.SUM))
    bi0, bo0 = _bytes()
    out = np.asarray(compress_world.reduce_scatter_block(
        compress_world.put(host), MPI.SUM))
    bi1, bo1 = _bytes()
    assert bi1 > bi0
    assert (bo1 - bo0) / (bi1 - bi0) <= 0.3
    assert out.shape == ref.shape
    scale = np.abs(ref).max()
    assert np.abs(out - ref).max() <= 0.02 * scale


def test_disabled_var_is_bit_identical_and_moves_no_extra_bytes(
        compress_world, world, rng):
    """Toggling the var off on an already-compressed comm delegates
    every call: results bit-identical to the plain path, zero new
    compress bytes."""
    n = world.size
    host = rng.normal(size=(n, 1 << 17)).astype(np.float32)
    ref = np.asarray(world.allreduce(world.put(host), MPI.SUM))
    var.var_set("mpi_base_compress", False)
    try:
        bi0, bo0 = _bytes()
        out = np.asarray(compress_world.allreduce(
            compress_world.put(host), MPI.SUM))
        bi1, bo1 = _bytes()
        assert (bi1, bo1) == (bi0, bo0), "bytes moved while disabled"
        assert np.array_equal(out, ref)
    finally:
        var.var_set("mpi_base_compress", True)


def test_non_sum_ops_fall_back_exact(compress_world, world, rng):
    """MPI reduction-op semantics: MAX (and every non-sum op) takes
    the uncompressed path even above the threshold — exact result,
    no compress bytes."""
    n = world.size
    host = rng.normal(size=(n, 1 << 17)).astype(np.float32)
    ref = np.asarray(world.allreduce(world.put(host), MPI.MAX))
    bi0, bo0 = _bytes()
    out = np.asarray(compress_world.allreduce(
        compress_world.put(host), MPI.MAX))
    bi1, bo1 = _bytes()
    assert (bi1, bo1) == (bi0, bo0)
    assert np.array_equal(out, ref)


def test_small_and_integer_payloads_fall_back_exact(compress_world,
                                                    world, rng):
    n = world.size
    small = rng.normal(size=(n, 64)).astype(np.float32)   # < threshold
    ref = np.asarray(world.allreduce(world.put(small), MPI.SUM))
    ints = rng.integers(0, 100, size=(n, 1 << 17)).astype(np.int32)
    refi = np.asarray(world.allreduce(world.put(ints), MPI.SUM))
    bi0, bo0 = _bytes()
    outs = np.asarray(compress_world.allreduce(
        compress_world.put(small), MPI.SUM))
    outi = np.asarray(compress_world.allreduce(
        compress_world.put(ints), MPI.SUM))
    assert _bytes() == (bi0, bo0)
    assert np.array_equal(outs, ref)
    assert np.array_equal(outi, refi)


def test_compressed_hier_inner_two_tier(compress_world, rng):
    """The hier schedule with the codec composed in (the multihost
    path, exercised over _groups' synthetic split on this flat mesh):
    only the slow-tier chunk quantizes; result within bound and
    bitwise identical across ranks."""
    from ompi_tpu.compress import codecs
    dev = compress_world.c_coll["allreduce"]
    while hasattr(dev, "_inner"):        # unwrap tracing shims if any
        dev = dev._inner
    dev = dev.device
    low, high = dev._groups()
    assert low is not None
    codec = (codecs.get_codec("int8_block"), 128)
    inner = dev._hier_allreduce_inner(MPI.SUM, low, high, codec)
    n = compress_world.size
    host = rng.normal(size=(n, 4096)).astype(np.float32)
    fn = dev._smap(inner, 2, 2)
    out = np.asarray(fn(compress_world.put(host)))
    ref = host.sum(axis=0, dtype=np.float64)
    assert np.abs(out[0].astype(np.float64) - ref).max() \
        <= 0.02 * np.abs(ref).max()
    for r in range(1, n):
        assert np.array_equal(out[0], out[r])


def test_allreduce_bind_routes_through_compressed(compress_world, rng):
    """MPI-4 persistent handle on a compressed comm: eligible example
    warms the compressed executable (bytes accounted per call);
    ineligible example binds the plain fast path."""
    n = compress_world.size
    host = rng.normal(size=(n, 1 << 17)).astype(np.float32)
    x = compress_world.put(host)
    bound = compress_world.allreduce_bind(x, MPI.SUM)
    bi0, _ = _bytes()
    y = np.asarray(bound(x))
    bi1, _ = _bytes()
    assert bi1 > bi0
    ref = host.sum(axis=0, dtype=np.float64)
    assert np.abs(y[0].astype(np.float64) - ref).max() \
        <= 0.02 * np.abs(ref).max()
    small = compress_world.put(
        rng.normal(size=(n, 2)).astype(np.float32))
    bsmall = compress_world.allreduce_bind(small, MPI.SUM)
    bi2, _ = _bytes()
    np.asarray(bsmall(small))
    assert _bytes()[0] == bi2            # plain path: no quant bytes


def test_decision_table_compression_rows_follow_the_var(world):
    """Satellite: the effective decision table (api/tool) shows
    compression rows only while mpi_base_compress is on, and
    decision_query answers without calling the collective."""
    from ompi_tpu.api import tool
    t_off = tool.decision_table(comm_size=world.size, platform="cpu")
    assert not any("compressed" in str(rule[2])
                   for rules in t_off.values() for rule in rules)
    q = tool.decision_query("allreduce", world.size, 8 << 20,
                            platform="cpu", op=MPI.SUM)
    assert q["compressed"] is False and q["algorithm"]
    var.var_set("mpi_base_compress", True)
    try:
        t_on = tool.decision_table(comm_size=world.size, platform="cpu")
        for func in ("allreduce", "allgather", "reduce_scatter_block"):
            rows = [r for r in t_on[func]
                    if str(r[2]).startswith("compressed:")]
            assert rows, f"no compression row for {func}"
            assert rows[-1][1] == (4 << 20)      # effective threshold
        assert not any(str(r[2]).startswith("compressed:")
                       for r in t_on["bcast"])
        q = tool.decision_query("allreduce", world.size, 8 << 20,
                                platform="cpu", dtype="float32",
                                op=MPI.SUM)
        assert q["compressed"] is True and q["codec"] == "int8_block"
        # non-sum op and ineligible dtype still answer uncompressed
        assert not tool.decision_query(
            "allreduce", world.size, 8 << 20, platform="cpu",
            op=MPI.MAX)["compressed"]
        assert not tool.decision_query(
            "allreduce", world.size, 8 << 20, platform="cpu",
            dtype="int32", op=MPI.SUM)["compressed"]
    finally:
        var.var_set("mpi_base_compress", False)
