"""Extended OSHMEM surface: wait/test, signals, locks, strided RMA,
strided alltoall, varying collect, named reductions, contexts.

Behavioral spec: ``oshmem/shmem/c`` entry points (SHMEM 1.4/1.5 —
wait_until/test, put_signal, set/test/clear_lock, iput/iget,
alltoalls, collect, ctx_create).
"""
import numpy as np
import pytest

from ompi_tpu.core.errhandler import MPIError
from ompi_tpu.shmem.api import (CMP_EQ, CMP_GE, CMP_LT, CMP_NE,
                                SIGNAL_ADD, SIGNAL_SET, ShmemCtx)


@pytest.fixture
def ctx(world):
    return ShmemCtx(world, heap_size=1 << 10, dtype=np.float64)


def test_wait_until_and_test(ctx):
    a = ctx.malloc(4)
    ctx.p(2, a, 7.0)
    assert ctx.test(2, a, CMP_EQ, 7.0)
    assert ctx.test(2, a, CMP_GE, 7.0)
    assert not ctx.test(2, a, CMP_LT, 7.0)
    ctx.wait_until(2, a, CMP_NE, 0.0)          # satisfied -> returns
    with pytest.raises(MPIError):
        ctx.wait_until(2, a, CMP_EQ, 99.0)     # deadlock surfaced


def test_put_signal_set_and_add(ctx):
    data = ctx.malloc(4)
    sig = ctx.malloc(1)
    ctx.put_signal(3, data, np.float64([1, 2, 3, 4]), sig, 1.0,
                   SIGNAL_SET)
    assert np.allclose(ctx.get(3, data, 4), [1, 2, 3, 4])
    assert ctx.signal_fetch(3, sig) == 1.0
    ctx.put_signal(3, data, np.float64([5, 6, 7, 8]), sig, 1.0,
                   SIGNAL_ADD)
    assert ctx.signal_fetch(3, sig) == 2.0
    ctx.signal_wait_until(3, sig, CMP_EQ, 2.0)


def test_locks(ctx):
    lk = ctx.malloc(1)
    assert ctx.test_lock(lk, pe=2)             # acquired
    assert not ctx.test_lock(lk, pe=5)         # contended
    with pytest.raises(MPIError):
        ctx.set_lock(lk, pe=5)                 # deadlock surfaced
    with pytest.raises(MPIError):
        ctx.clear_lock(lk, pe=5)               # not the holder
    ctx.clear_lock(lk, pe=2)
    ctx.set_lock(lk, pe=5)                     # now free
    ctx.clear_lock(lk, pe=5)


def test_iput_iget_strided(ctx):
    a = ctx.malloc(16)
    ctx.put(1, a, np.zeros(16))
    ctx.iput(1, a, np.float64([1, 2, 3, 4]), tst=2)
    row = ctx.get(1, a, 8)
    assert np.allclose(row, [1, 0, 2, 0, 3, 0, 4, 0])
    got = ctx.iget(1, a, 4, sst=2)
    assert np.allclose(got, [1, 2, 3, 4])
    # target stride spaces elements locally (mirrors iput), never drops
    spaced = ctx.iget(1, a, 4, tst=2, sst=2)
    assert np.allclose(spaced, [1, 0, 2, 0, 3, 0, 4])


def test_alltoalls_strided(ctx):
    n = ctx.n_pes
    a = ctx.malloc(2 * n)
    for pe in range(n):                        # PE pe's block j = pe*10+j
        ctx.put(pe, a, np.float64([pe * 10 + j for j in range(n)]))
    ctx.alltoalls(a, 1, dst=1, sst=1)
    for pe in range(n):
        got = ctx.get(pe, a, n)
        assert np.allclose(got, [i * 10 + pe for i in range(n)])


def test_collect_varying_and_fcollect(ctx):
    n = ctx.n_pes
    a = ctx.malloc(4)
    for pe in range(n):
        ctx.put(pe, a, np.float64([pe, pe, pe, pe]))
    assert np.allclose(ctx.fcollect(a, 2),
                       np.repeat(np.arange(n), 2))
    sizes = [1 + (pe % 2) for pe in range(n)]
    got = ctx.collect_varying(a, sizes)
    want = np.concatenate([[pe] * s for pe, s in enumerate(sizes)])
    assert np.allclose(got, want)


def test_named_reductions(ctx):
    a = ctx.malloc(2)
    for pe in range(ctx.n_pes):
        ctx.put(pe, a, np.float64([pe + 1, 1.0]))
    ctx.max_to_all(a, 2)
    assert np.allclose(ctx.get(0, a, 2), [ctx.n_pes, 1.0])
    for pe in range(ctx.n_pes):
        ctx.put(pe, a, np.float64([pe + 1, 2.0]))
    ctx.sum_to_all(a, 2)
    n = ctx.n_pes
    assert np.allclose(ctx.get(3, a, 2), [n * (n + 1) / 2, 2.0 * n])


def test_named_bitwise_reductions(world):
    ctx = ShmemCtx(world, heap_size=1 << 8, dtype=np.int64)
    a = ctx.malloc(1)
    for pe in range(ctx.n_pes):
        ctx.p(pe, a, 1 << pe)
    ctx.or_to_all(a, 1)
    assert int(ctx.g(0, a)) == (1 << ctx.n_pes) - 1


def test_ctx_create_scope(ctx):
    c = ctx.ctx_create()
    a = ctx.malloc(2)
    c.put(1, a, np.float64([4, 5]))
    assert c.pending_ops == 1
    c.quiet()
    assert c.pending_ops == 0
    assert np.allclose(ctx.get(1, a, 2), [4, 5])
    c.destroy()


def test_ptr_snapshot(ctx):
    a = ctx.malloc(2)
    ctx.put(2, a, np.float64([8, 9]))
    snap = ctx.ptr(2)
    assert np.allclose(snap[a:a + 2], [8, 9])


@pytest.fixture
def ictx(world):
    """Integer-heap context for bitwise atomics."""
    return ShmemCtx(world, heap_size=1 << 10, dtype=np.int64)


def test_heap_calloc_realloc_align_free(ctx):
    a = ctx.calloc(8)
    assert np.allclose(ctx.get(1, a, 8), 0.0)
    ctx.p(1, a, 42.0)
    b = ctx.realloc(a, 16)                     # content moves
    assert ctx.get(1, b, 1)[0] == 42.0
    with pytest.raises(MPIError):
        ctx.free(a)                            # a was freed by realloc
    c = ctx.align(8, 4)
    assert c % 8 == 0
    ctx.free(c)
    with pytest.raises(MPIError):
        ctx.free(c)                            # double free surfaced


def test_atomic_inc_and_fetch_inc(ctx):
    a = ctx.malloc(1)
    ctx.p(2, a, 10.0)
    ctx.atomic_inc(2, a)
    assert ctx.atomic_fetch_inc(2, a) == 11.0
    assert ctx.g(2, a) == 12.0


def test_bitwise_atomics(ictx):
    a = ictx.malloc(1)
    ictx.p(1, a, 0b1100)
    ictx.atomic_and(1, a, 0b1010)
    assert ictx.g(1, a) == 0b1000
    ictx.atomic_or(1, a, 0b0001)
    assert ictx.g(1, a) == 0b1001
    old = ictx.atomic_fetch_xor(1, a, 0b1111)
    assert old == 0b1001 and ictx.g(1, a) == 0b0110
    assert ictx.atomic_fetch_and(1, a, 0b0010) == 0b0110
    assert ictx.atomic_fetch_or(1, a, 0b1000) == 0b0010


def test_ivars_test_and_wait(ctx):
    offs = [ctx.malloc(1) for _ in range(3)]
    ctx.p(0, offs[0], 5.0)
    ctx.p(0, offs[2], 5.0)
    assert not ctx.test_all(0, offs, CMP_EQ, 5.0)
    assert ctx.test_any(0, offs, CMP_EQ, 5.0) == 0
    assert ctx.test_some(0, offs, CMP_EQ, 5.0) == [0, 2]
    ctx.p(0, offs[1], 5.0)
    ctx.wait_until_all(0, offs, CMP_EQ, 5.0)   # satisfied
    assert ctx.wait_until_any(0, offs, CMP_EQ, 5.0) == 0
    assert ctx.wait_until_some(0, offs, CMP_NE, 9.0) == [0, 1, 2]
    with pytest.raises(MPIError):
        ctx.wait_until_all(0, offs, CMP_EQ, 99.0)  # deadlock surfaced


def test_accessibility_info_pcontrol_cache(ctx):
    assert ctx.pe_accessible(ctx.n_pes - 1)
    assert not ctx.pe_accessible(ctx.n_pes)
    a = ctx.malloc(2)
    assert ctx.addr_accessible(a, 0)
    assert not ctx.addr_accessible(1 << 30, 0)
    assert ctx.info_get_version() == (1, 5)
    assert "OpenSHMEM" in ctx.info_get_name()
    ctx.pcontrol(2)                            # SPC-recorded no-op
    ctx.clear_cache_inv()                      # deprecated no-ops
    ctx.set_cache_inv()
    ctx.udcflush()


def test_active_set_barrier_and_sync(ctx):
    ctx.sync_all()
    # PEs {0, 2, 4, ...}: stride 2^1 active set
    ctx.barrier(0, 1, ctx.n_pes // 2)


def test_global_exit_raises_systemexit(ctx):
    with pytest.raises(SystemExit):
        ctx.global_exit(3)
