"""ULFM fault tolerance: revoke / shrink / agree / failure_ack.

Mirrors the reference's ULFM semantics (docs/features/ulfm.rst,
ompi/mpiext/ftmpi, coll/ftagree, request-level FT in
ompi/request/req_ft.c) exercised through injected failures — the
fault-injection surface the reference delegates to external harnesses.
"""
import numpy as np
import pytest

from ompi_tpu.core.errhandler import (ERR_PROC_FAILED, ERR_REVOKED,
                                      MPIError)
from ompi_tpu.mpiext import ftmpi
from ompi_tpu.runtime import ft


@pytest.fixture()
def comm(world):
    """A dup of COMM_WORLD with a clean failure registry, so injected
    failures never leak into other tests."""
    ft._reset_for_tests()
    c = world.dup()
    c.set_errhandler(__import__("ompi_tpu").ERRORS_RETURN)
    yield c
    ft._reset_for_tests()


def test_collective_raises_proc_failed(comm):
    x = comm.alloc((4,), np.float32, fill=1.0)
    assert float(np.asarray(comm.allreduce(x))[0, 0]) == comm.size
    ftmpi.fail_rank(comm.group.world_ranks[1], "test kill")
    with pytest.raises(MPIError) as ei:
        comm.allreduce(x)
    assert ei.value.error_class == ERR_PROC_FAILED


def test_shrink_produces_working_comm(comm):
    n = comm.size
    ftmpi.fail_rank(comm.group.world_ranks[1])
    ftmpi.fail_rank(comm.group.world_ranks[3])
    small = ftmpi.Comm_shrink(comm)
    assert small.size == n - 2
    assert comm.group.world_ranks[1] not in small.group.world_ranks
    x = small.alloc((4,), np.float32, fill=1.0)
    y = small.allreduce(x)
    assert float(np.asarray(y)[0, 0]) == small.size


def test_agree_masks_and_flags_failures(comm):
    # No failures: plain AND agreement.
    flags = [0b111] * comm.size
    flags[2] = 0b101
    assert comm.agree(flags) == 0b101
    # With an unacked failure: agreement still reached, error raised,
    # dead rank's contribution excluded.
    ftmpi.fail_rank(comm.group.world_ranks[2])
    with pytest.raises(MPIError) as ei:
        comm.agree(flags)
    assert ei.value.error_class == ERR_PROC_FAILED
    assert ei.value.agreed_value == 0b111      # rank 2's 0b101 excluded
    # Acknowledge -> agree is quiet again.
    ftmpi.Comm_failure_ack(comm)
    assert comm.agree(flags) == 0b111


def test_iagree_and_ishrink(comm):
    req = ftmpi.Comm_iagree(comm, [1] * comm.size)
    assert req.wait() is not None
    assert req.get() == 1
    ftmpi.fail_rank(comm.group.world_ranks[0])
    sreq = ftmpi.Comm_ishrink(comm)
    sreq.wait()
    assert sreq.get().size == comm.size - 1


def test_failure_ack_and_get_acked(comm):
    assert ftmpi.Comm_failure_get_acked(comm).size == 0
    wr = comm.group.world_ranks[1]
    ftmpi.fail_rank(wr)
    assert ftmpi.Comm_get_failed(comm).size == 1
    assert ftmpi.Comm_failure_get_acked(comm).size == 0
    ftmpi.Comm_failure_ack(comm)
    acked = ftmpi.Comm_failure_get_acked(comm)
    assert acked.size == 1 and acked.world_ranks[0] == wr


def test_ack_failed_partial(comm):
    for r in (1, 2):
        ftmpi.fail_rank(comm.group.world_ranks[r])
    g = ftmpi.Comm_ack_failed(comm, 1)
    assert g.size == 1
    g = ftmpi.Comm_ack_failed(comm)
    assert g.size == 2


def test_pt2pt_to_failed_peer_raises(comm):
    ftmpi.fail_rank(comm.group.world_ranks[2])
    with pytest.raises(MPIError) as ei:
        comm.send(np.ones(2, np.float32), src=0, dest=2, tag=7)
    assert ei.value.error_class == ERR_PROC_FAILED
    with pytest.raises(MPIError) as ei:
        comm.recv(source=2, tag=7, dst=0)
    assert ei.value.error_class == ERR_PROC_FAILED


def test_sendrecv_checks_both_peers(comm):
    ftmpi.fail_rank(comm.group.world_ranks[2])
    with pytest.raises(MPIError) as ei:
        comm.sendrecv(np.ones(1, np.float32), src=0, dest=2,
                      recvsource=1)
    assert ei.value.error_class == ERR_PROC_FAILED
    with pytest.raises(MPIError) as ei:
        comm.sendrecv(np.ones(1, np.float32), src=0, dest=1,
                      recvsource=2)
    assert ei.value.error_class == ERR_PROC_FAILED


def test_anysource_needs_ack(comm):
    ftmpi.fail_rank(comm.group.world_ranks[1])
    with pytest.raises(MPIError) as ei:
        comm.recv(source=-1, tag=7, dst=0)
    assert ei.value.error_class == ERR_PROC_FAILED
    ftmpi.Comm_failure_ack(comm)
    # Acked: wildcard receive is re-armed and sees a live sender's message.
    comm.send(np.full(2, 5.0, np.float32), src=0, dest=3, tag=7)
    data, st = comm.recv(source=-1, tag=7, dst=3)
    assert st.source == 0 and float(data[0]) == 5.0


def test_pending_irecv_fails_when_peer_dies(comm):
    req = comm.irecv(source=2, tag=9, dst=0)
    ftmpi.fail_rank(comm.group.world_ranks[2])
    with pytest.raises(MPIError) as ei:
        req.wait()
    assert ei.value.error_class == ERR_PROC_FAILED


def test_revoke_blocks_ops_but_not_shrink_agree(comm):
    ftmpi.Comm_revoke(comm)
    assert ftmpi.Comm_is_revoked(comm)
    x = comm.alloc((2,), np.float32, fill=1.0)
    with pytest.raises(MPIError) as ei:
        comm.allreduce(x)
    assert ei.value.error_class == ERR_REVOKED
    with pytest.raises(MPIError):
        comm.send(np.ones(1), src=0, dest=1)
    # ULFM: agree and shrink still work on a revoked communicator.
    assert comm.agree([3] * comm.size) == 3
    fresh = ftmpi.Comm_shrink(comm)
    assert fresh.size == comm.size and not fresh.is_revoked()


def test_pending_irecv_observes_revoke(comm):
    req = comm.irecv(source=1, tag=3, dst=0)
    ftmpi.Comm_revoke(comm)
    with pytest.raises(MPIError) as ei:
        req.wait()
    assert ei.value.error_class == ERR_REVOKED


def test_failure_listener_epoch(comm):
    events = []
    ftmpi.add_failure_listener(lambda r, why: events.append((r, why)))
    e0 = ftmpi.failure_epoch()
    ftmpi.fail_rank(comm.group.world_ranks[0], "kill")
    ftmpi.fail_rank(comm.group.world_ranks[0], "kill-again")  # dedup
    assert ftmpi.failure_epoch() == e0 + 1
    assert events == [(comm.group.world_ranks[0], "kill")]


def test_ftagree_tree_structure():
    """The agreement value must be the AND of live contributions only,
    for every failure pattern (exhaustive over 4 ranks)."""
    from ompi_tpu.coll.ftagree import _tree_agree
    contribs = [0b1111, 0b1110, 0b1101, 0b1011]
    for mask in range(16):
        alive = [(mask >> r) & 1 == 1 for r in range(4)]
        expect = ~0
        for r in range(4):
            if alive[r]:
                expect &= contribs[r]
        assert _tree_agree(contribs, alive) == expect


def test_probe_devices_healthy(comm):
    assert ftmpi.probe_devices(comm.devices) == []
    assert ftmpi.failed_ranks() == frozenset()
