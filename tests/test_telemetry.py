"""Telemetry plane unit tier (docs/OBSERVABILITY.md): the off =
byte-identical gate, lock-free histogram shards under concurrent
writers, percentile/merge math, the straggler hysteresis with a
synthetic clock, per-comm pvar retirement, flight-recorder record /
rate-limit / merge semantics, tracedump's skip + ``--strict``
contract, mpitop's merged summary, and the Prometheus exporter."""
import json
import os
import threading

import numpy as np
import pytest

from ompi_tpu import telemetry
from ompi_tpu.mca import pvar, var
from ompi_tpu.telemetry import flightrec, health, prom
from ompi_tpu.telemetry import hist as hist_mod
from ompi_tpu.telemetry.hist import (Histogram, bucket_bounds,
                                     merge_snapshots,
                                     percentile_from_buckets)


@pytest.fixture()
def tele():
    """The plane armed for one test, fully torn down after — the
    session default stays OFF (other tests assert byte-identity)."""
    telemetry._reset_for_tests()
    flightrec._reset_for_tests()
    telemetry.enable()
    yield telemetry
    for h in telemetry.histograms():
        if h.registered:
            pvar.pvar_unregister(h.name)
    telemetry.disable()
    telemetry._reset_for_tests()
    flightrec._reset_for_tests()


def _standalone(name, values=(), labels=None):
    """A histogram outside the registry: ``registered`` pre-set so
    recording never touches the pvar surface."""
    h = Histogram(name, labels=labels)
    h.registered = True
    for v in values:
        h.record(v)
    return h


def _hist_row(name, values, labels=None):
    h = _standalone(name, values, labels)
    return {"name": name, "unit": "us", "comm": None,
            "labels": dict(labels or {}), "snap": h.snapshot()}


# -- the off gate: byte-identical, zero-touch --------------------------------
def test_telemetry_off_hot_paths_untouched(monkeypatch, world):
    """Telemetry off (the default): every hot-path gate is ONE
    attribute read — no histogram may be started or recorded by the
    stacked collectives or the per-rank pml."""
    def boom(*a, **kw):
        raise AssertionError("histogram touched while disabled")
    monkeypatch.setattr(Histogram, "record", boom)
    monkeypatch.setattr(Histogram, "start", boom)
    assert telemetry.active is False
    assert telemetry.telemetry_enabled() is False

    # stacked collective entry (the composer never wrapped the vtable)
    from ompi_tpu.telemetry import _HistSlot
    for func, mod in world.c_coll.items():
        assert not isinstance(mod, _HistSlot), func
    x = world.alloc((2,), np.float32, fill=1.0)
    world.allreduce(x)

    # per-rank pml entry (loopback engine): send/recv/send_small
    from ompi_tpu.pml.perrank import PerRankEngine, Router
    kv = {}
    router = Router(0, 1, kv.__setitem__, kv.__getitem__)

    class _C:
        cid = "tele-off"
        size = 2

        def rank(self):
            return 0

        def world_rank_of(self, r):
            return 0
    eng = PerRankEngine(_C(), router)
    try:
        eng.send(np.float32(1.0), dest=1, tag=5)
        eng.recv(source=0, tag=5, timeout=10)
        eng.send_small(np.float32(2.0), [1], tag=6)
        eng.recv(source=0, tag=6, timeout=10)
    finally:
        router.close()


def test_enable_arms_core_hists_and_disable_keeps_them_readable():
    telemetry._reset_for_tests()
    assert telemetry.PML_SEND is None
    try:
        telemetry.enable()
        assert telemetry.active
        for h in (telemetry.PML_SEND, telemetry.PML_RECV,
                  telemetry.SEGMENT, telemetry.FLUSH, telemetry.RAIL,
                  telemetry.HB_GAP, telemetry.HB_RTT):
            assert isinstance(h, Histogram)
        telemetry.PML_SEND.record(123.0)
        telemetry.disable()
        assert telemetry.active is False
        # readable post-mortem, like the trace ring
        assert telemetry.PML_SEND.snapshot()["count"] == 1
    finally:
        pvar.pvar_unregister("tele_pml_send_us")
        telemetry._reset_for_tests()


# -- histogram math ----------------------------------------------------------
def test_histogram_buckets_percentiles_and_bounds():
    h = _standalone("t", [0, 1, 10, 100, 1000, -5])  # -5 clamps to 0
    m = h.merged()
    assert m["count"] == 6
    assert m["buckets"][0] == 2          # 0 and clamp(-5)
    assert m["buckets"][1] == 1          # 1 -> [1, 2)
    snap = h.snapshot()
    assert snap["count"] == 6
    assert snap["p50"] <= snap["p90"] <= snap["p99"] <= \
        bucket_bounds(hist_mod.NBUCKETS - 1)[1]
    assert snap["max"] == 1000.0
    lo, hi = bucket_bounds(0)
    assert (lo, hi) == (0.0, 1.0)
    for i in range(1, 12):
        lo, hi = bucket_bounds(i)
        assert hi == 2 * lo

    # sparse and dense derivations agree
    dense = m["buckets"]
    sparse = {str(i): n for i, n in enumerate(dense) if n}
    for p in (50, 90, 99):
        assert percentile_from_buckets(dense, m["count"], p) == \
            percentile_from_buckets(sparse, m["count"], p)
    assert percentile_from_buckets([], 0, 99) == 0.0


def test_histogram_observe_token_and_none_noop():
    h = _standalone("t2")
    h.observe(None)                      # the gated idiom's off branch
    assert h.merged()["count"] == 0
    tok = h.start()
    h.observe(tok)
    m = h.merged()
    assert m["count"] == 1 and m["sum"] >= 0.0


def test_histogram_concurrent_writers_merge():
    """The shard contract: 4 writer threads, no lock on the record
    path, and the merged read sees every sample exactly once."""
    h = _standalone("conc")
    PER = 1000

    def w(k):
        for i in range(PER):
            h.record(k * 1000 + i)

    ts = [threading.Thread(target=w, args=(k,)) for k in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    m = h.merged()
    assert m["count"] == 4 * PER
    assert sum(m["buckets"]) == 4 * PER
    assert m["max"] == 3999.0
    assert len(h._shards) == 4           # one shard per writer thread
    h.reset()
    assert h.merged()["count"] == 0
    assert len(h._shards) == 4           # shards survive the window


def test_merge_snapshots_cross_rank():
    a = _standalone("a", [10] * 99 + [5000])
    b = _standalone("b", [10] * 100)
    m = merge_snapshots([a.snapshot(), b.snapshot(), {}])
    assert m["count"] == 200
    assert m["max"] == 5000.0
    assert m["p50"] <= 16.0              # bucket of 10 tops out at 16
    assert m["p99"] >= m["p50"]
    assert sum(int(n) for n in m["buckets"].values()) == 200


def test_size_class_and_cid_token():
    assert [telemetry.size_class(n) for n in
            (0, 1024, 1025, 65536, 65537, 1 << 20, (1 << 20) + 1)] == \
        [0, 0, 1, 1, 2, 2, 3]
    assert telemetry._cid_token("world") == "world"
    assert telemetry._cid_token(("split", 3)) != ""
    assert telemetry._cid_token("") == "none"


# -- straggler hysteresis ----------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_straggler_hysteresis_declare_and_recover():
    """Score over threshold must persist ``miss`` consecutive samples
    before telemetry.straggler fires; a recovered peer (score under
    half the threshold) is cleared and may be re-declared."""
    from ompi_tpu.utils import hooks
    events = []
    handle = hooks.register_profiler(
        lambda ev, comm, info: events.append((ev, info["rank"]))
        if ev.startswith("telemetry.") else None)
    clock = _Clock()
    mon = health.HealthMonitor(0, 4, sample_s=1e9, window_s=10.0,
                               threshold=0.05, miss=3, clock=clock)
    try:
        clock.t = 1.0
        for peer in (2, 3):              # the cross-peer median floor
            mon.note_wait(peer, 0.001)
        mon.note_wait(1, 0.8)            # 0.8s outlier -> score ~0.08

        clock.t = 1.1
        assert mon.sample()[1] >= 0.05
        assert mon.declared() == []      # miss 1 of 3
        clock.t = 1.2
        mon.sample()
        assert mon.declared() == []      # miss 2 of 3
        clock.t = 1.3
        mon.sample()
        assert mon.declared() == [1]     # declared on the 3rd
        assert mon.stats["stragglers"] == 1
        assert ("telemetry.straggler", 1) in events

        clock.t = 1.4                    # still over: no re-fire
        mon.sample()
        assert mon.stats["stragglers"] == 1

        clock.t = 20.0                   # window empties -> score 0
        scores = mon.sample()
        assert scores[1] == 0.0
        assert mon.declared() == []
        assert mon.stats["recovered"] == 1
        assert ("telemetry.recovered", 1) in events

        # re-declaration after recovery is allowed
        for peer in (2, 3):
            mon.note_wait(peer, 0.001)
        mon.note_wait(1, 0.9)
        for i in range(3):
            clock.t = 20.1 + i * 0.1
            mon.sample()
        assert mon.declared() == [1]
        assert mon.stats["stragglers"] == 2
    finally:
        hooks.unregister_profiler(handle)


def test_straggler_needs_two_peers_for_median():
    """One noisy peer alone scores raw waits (median 0 needs >= 2
    peers) — but a uniformly slow phase with every peer equally slow
    scores nobody above the self-cancelling median."""
    clock = _Clock()
    mon = health.HealthMonitor(0, 4, sample_s=1e9, window_s=10.0,
                               threshold=0.05, miss=1, clock=clock)
    clock.t = 1.0
    for peer in (1, 2, 3):
        mon.note_wait(peer, 0.7)         # everyone equally slow
    clock.t = 1.1
    scores = mon.sample()
    # median 0.7 cancels: nobody is an outlier among peers
    assert all(s < 0.05 for s in scores.values()), scores
    assert mon.declared() == []


def test_degraded_episode_latches(tele):
    var.var_set("mpi_base_telemetry_degraded_ms", 1.0)
    try:
        mon = health.HealthMonitor(0, 2, sample_s=1e9, window_s=10.0,
                                   threshold=0.05, miss=3,
                                   clock=_Clock())
        tele.PML_SEND.record(50_000.0)   # own send p99 = 50 ms >> 1 ms
        mon.sample(1.0)
        assert mon.stats["degraded"] == 1
        mon.sample(1.1)                  # episode latch: no re-count
        assert mon.stats["degraded"] == 1
        tele.PML_SEND.reset()            # p99 back under the limit
        mon.sample(1.2)
        tele.PML_SEND.record(50_000.0)   # a NEW episode counts again
        mon.sample(1.3)
        assert mon.stats["degraded"] == 2
    finally:
        var.var_set("mpi_base_telemetry_degraded_ms", 0.0)


# -- per-comm retirement -----------------------------------------------------
def test_retire_comm_drops_hists_and_pvars(tele):
    hists = telemetry.coll_hists("c77", "allreduce")
    assert len(hists) == len(telemetry.SIZE_CLASS_NAMES)
    for h in hists:
        h.record(10.0)                   # first record registers pvars
    names = {h.name for h in hists}
    assert names <= set(pvar.pvar_names())
    keep = telemetry.get_hist("tele_unrelated_us")
    keep.record(1.0)

    retired = telemetry.retire_comm("c77")
    assert names <= set(retired)
    assert not (names & set(pvar.pvar_names()))
    live = {h.name for h in telemetry.histograms()}
    assert not (names & live)
    assert "tele_unrelated_us" in live   # other comms untouched
    # idempotent: a second retirement finds nothing
    assert not (names & set(telemetry.retire_comm("c77")))


def test_retire_comm_drops_trace_skew_pvar():
    from ompi_tpu.trace import attribution
    attribution._note_skew("88", 0.25)
    assert "trace_skew_c88" in pvar.pvar_names()
    assert "88" in attribution.skew_watermarks()
    retired = telemetry.retire_comm("88")
    assert "trace_skew_c88" in retired
    assert "trace_skew_c88" not in pvar.pvar_names()
    assert "88" not in attribution.skew_watermarks()


# -- flight recorder ---------------------------------------------------------
def test_flightrec_inactive_refuses():
    flightrec._reset_for_tests()
    assert telemetry.active is False
    assert flightrec.record("straggler", {"rank": 1}) is None


def test_flightrec_record_rate_limit_and_siblings(tele, tmp_path):
    var.var_set("mpi_base_telemetry_flightrec_dir", str(tmp_path))
    try:
        flightrec.arm(7)
        p1 = flightrec.record("straggler", {"rank": 3})
        assert p1 is not None
        assert os.path.basename(p1) == "flightrec_7.json"
        d = json.loads(open(p1).read())
        assert d["flightrec"] == 1 and d["rank"] == 7
        assert d["trigger"] == "straggler"
        assert d["detail"] == {"rank": 3}
        for key in ("spans", "pvars", "ft_events", "health",
                    "wall_time"):
            assert key in d, key
        # rate limit: the same (trigger, subject) never fires twice
        assert flightrec.record("straggler", {"rank": 3}) is None
        # a different subject writes a suffixed SIBLING — the first
        # snapshot (and its accusation) must survive
        p2 = flightrec.record("revoke", {"rank": 7})
        assert p2 is not None and p2 != p1
        assert os.path.exists(p1) and os.path.exists(p2)
        assert not [f for f in os.listdir(tmp_path)
                    if ".tmp." in f]     # atomic: no torn leftovers
    finally:
        var.var_set("mpi_base_telemetry_flightrec_dir", "")


def test_flightrec_merge_elects_critical_and_absent():
    pays = [
        {"flightrec": 1, "rank": 0, "trigger": "proc_failed",
         "detail": {"rank": 2}, "wall_time": 2.0,
         "spans": [{"rank": 2, "name": "coll_allreduce"}],
         "health": {}},
        {"flightrec": 1, "rank": 1, "trigger": "proc_failed",
         "detail": {"rank": 2}, "wall_time": 1.0, "spans": [],
         "health": {}},
        {"flightrec": 1, "rank": 0, "trigger": "revoke",
         "detail": {"rank": 0}, "wall_time": 3.0},
    ]
    rep = flightrec.merge(pays)
    assert rep["incident"] == 1
    assert rep["critical_rank"] == 2
    assert rep.get("critical_absent") is True     # rank 2 never wrote
    assert rep["accusations"] == {"2": 2}         # revoke doesn't accuse
    times = [t["wall_time"] for t in rep["triggers"]]
    assert times == sorted(times)
    # the accusers' spans FOR the critical rank are the evidence
    assert rep["critical_spans"] == [{"rank": 2,
                                      "name": "coll_allreduce"}]


def test_flightrec_merge_fallback_worst_p99():
    pays = [
        {"flightrec": 1, "rank": 0, "trigger": "revoke", "detail": {},
         "pvars": {"tele_pml_send_us": {"p99": 10.0, "count": 5}},
         "spans": [], "health": {}},
        {"flightrec": 1, "rank": 1, "trigger": "revoke", "detail": {},
         "pvars": {"tele_pml_send_us": {"p99": 9000.0, "count": 5}},
         "spans": [{"rank": 1, "name": "pml_send"}], "health": {}},
    ]
    rep = flightrec.merge(pays)
    assert rep["accusations"] == {}
    assert rep["critical_rank"] == 1              # worst own p99
    assert rep["critical_spans"] == [{"rank": 1, "name": "pml_send"}]
    assert "critical_absent" not in rep


# -- tracedump: skip + --strict ----------------------------------------------
def test_tracedump_skips_truncated_and_strict(tmp_path, capsys):
    from ompi_tpu.tools import tracedump
    good = tmp_path / "trace_0.json"
    good.write_text(json.dumps({"rank": 0, "offset_s": 0.0,
                                "spans": []}))
    bad = tmp_path / "trace_1.json"
    bad.write_text('{"rank": 1, "spans": [')     # truncated mid-write
    out = tmp_path / "sum.json"

    rc = tracedump.main(["--format", "summary", "-o", str(out),
                         str(good), str(bad)])
    assert rc == 0                       # skip, don't die
    err = capsys.readouterr().err
    assert "skipped" in err and "trace_1.json" in err
    rep = json.loads(out.read_text())
    assert rep["skipped"] == 1
    assert rep["skipped_files"][0]["file"] == str(bad)

    # --strict turns any skip into a nonzero exit for CI
    rc = tracedump.main(["--format", "summary", "-o", str(out),
                         "--strict", str(good), str(bad)])
    assert rc == 1
    capsys.readouterr()
    rc = tracedump.main(["--format", "summary", "-o", str(out),
                         "--strict", str(good)])
    assert rc == 0                       # all-readable strict run


def test_tracedump_flightrec_format(tmp_path):
    from ompi_tpu.tools import tracedump
    for rank in (0, 1):
        (tmp_path / f"flightrec_{rank}.json").write_text(json.dumps(
            {"flightrec": 1, "rank": rank, "trigger": "proc_failed",
             "detail": {"rank": 3}, "wall_time": float(rank),
             "spans": [], "health": {}}))
    out = tmp_path / "incident.json"
    rc = tracedump.main(["--format", "flightrec", "-o", str(out),
                         str(tmp_path / "flightrec_0.json"),
                         str(tmp_path / "flightrec_1.json")])
    assert rc == 0
    rep = json.loads(out.read_text())
    assert rep["incident"] == 1
    assert rep["critical_rank"] == 3
    assert rep["accusations"] == {"3": 2}


# -- mpitop ------------------------------------------------------------------
def _dump(rank, hists, health_snap=None, t=100.0):
    return {"telemetry": 1, "rank": rank, "time": t, "hists": hists,
            "health": health_snap or {}}


def test_mpitop_summarize_elects_declared_straggler():
    from ompi_tpu.tools import mpitop
    coll_labels = {"comm": "w", "func": "allreduce", "sclass": "small"}
    snaps = [
        _dump(0, [_hist_row("tele_coll_allreduce_cw_small", [100] * 10,
                            coll_labels),
                  _hist_row("tele_pml_send_us", [50] * 10)],
              {"scores": {"1": 0.3}, "declared": [1]}),
        _dump(1, [_hist_row("tele_coll_allreduce_cw_small",
                            [200_000] * 10, coll_labels),
                  _hist_row("tele_pml_send_us", [200_000] * 10)]),
    ]
    s = mpitop.summarize(snaps)
    assert s["mpitop"] == 1
    assert s["slow_rank"] == 1
    assert s["declared"] == {"1": 1}
    assert s["accusations"]["1"] == 0.3
    rows = {r["rank"]: r for r in s["rows"]}
    assert rows[0]["coll_ops"] == 10
    assert rows[1]["send_p99_us"] >= 131072     # bucket of 200k
    assert rows[1]["straggler_score"] == 0.3
    assert rows[1]["declared_by"] == 1

    table = mpitop.render_table(s)
    assert "STRAGGLER(x1)" in table
    assert "SLOW" in table
    assert table.splitlines()[-1] == "slow_rank: 1"

    # per-comm expansion keys rows on the histogram comm label
    per = mpitop.summarize(snaps, per_comm=True)
    assert any(r.get("comm") == "w" for r in per["rows"])


def test_mpitop_slow_rank_fallback_excludes_recv_waits():
    """With no accusations the election is OWN latency only — the rank
    stuck waiting (big recv p99) must not be blamed for its peer."""
    from ompi_tpu.tools import mpitop
    snaps = [
        _dump(0, [_hist_row("tele_pml_recv_us", [500_000] * 5),
                  _hist_row("tele_pml_send_us", [50] * 5)]),
        _dump(1, [_hist_row("tele_pml_send_us", [200_000] * 5)]),
    ]
    s = mpitop.summarize(snaps)
    assert s["declared"] == {} and s["accusations"] == {}
    assert s["slow_rank"] == 1


def test_mpitop_load_snapshots_skips_garbage(tmp_path, capsys):
    from ompi_tpu.tools import mpitop
    good = tmp_path / "telemetry_0.json"
    good.write_text(json.dumps(_dump(0, [])))
    bad = tmp_path / "telemetry_1.json"
    bad.write_text("{not json")
    snaps, skipped = mpitop.load_snapshots([str(good), str(bad)])
    assert len(snaps) == 1 and snaps[0]["rank"] == 0
    assert len(skipped) == 1 and skipped[0]["file"] == str(bad)
    assert "telemetry_1.json" in capsys.readouterr().err


# -- Prometheus exporter -----------------------------------------------------
def test_prom_render_histogram_cumulative_and_gauge(tele, tmp_path):
    h = telemetry.get_hist("tele_demo_us", labels={"func": "demo"})
    for v in (1, 10, 100, 1000):
        h.record(v)
    pvar.pvar_register("tele_demo_gauge", lambda: 7,
                       help="prom exporter test gauge")
    try:
        text = prom.render(rank=3)
        assert "# TYPE ompi_tpu_tele_demo_us histogram" in text
        assert "# HELP ompi_tpu_tele_demo_us" in text
        # cumulative buckets end at +Inf == count
        assert ('ompi_tpu_tele_demo_us_bucket{func="demo",le="+Inf",'
                'rank="3"} 4') in text
        assert 'ompi_tpu_tele_demo_us_count{func="demo",rank="3"} 4' \
            in text
        assert 'ompi_tpu_tele_demo_us_sum{func="demo",rank="3"} 1111' \
            in text
        cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                if line.startswith("ompi_tpu_tele_demo_us_bucket")]
        assert cums == sorted(cums) and cums[-1] == 4
        assert "# TYPE ompi_tpu_tele_demo_gauge gauge" in text
        assert 'ompi_tpu_tele_demo_gauge{rank="3"} 7' in text
        # the histogram pvar must NOT double-render as a gauge
        assert text.count("# TYPE ompi_tpu_tele_demo_us ") == 1

        out = tmp_path / "telemetry.prom"
        prom.write_textfile(str(out), text)
        assert out.read_text() == text
        assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    finally:
        pvar.pvar_unregister("tele_demo_gauge")


def test_prom_merged_rows_collapse_per_comm_families():
    labels = {"comm": "w", "func": "allreduce", "sclass": "small"}
    row = dict(_hist_row("tele_coll_allreduce_cw_small", [5, 9],
                         labels), rank=2)
    text = prom.render(rank=-1, pvars=[], hist_rows=[row])
    # the _c<tok>_<sclass> suffix collapses into ONE metric family;
    # comm/func/sclass ride as labels
    assert "# TYPE ompi_tpu_tele_coll_allreduce histogram" in text
    assert "tele_coll_allreduce_cw_small" not in text
    assert ('ompi_tpu_tele_coll_allreduce_count{comm="w",'
            'func="allreduce",rank="2",sclass="small"} 2') in text


def test_prom_dict_valued_pvar_one_sample_per_key():
    text = prom.render(rank=0, pvars=[
        {"name": "tele_straggler_scores", "class": "level",
         "value": {"1": 0.25, "3": 0.0}}], hist_rows=[])
    assert ('ompi_tpu_tele_straggler_scores{key="1",rank="0"} 0.25'
            in text)
    assert ('ompi_tpu_tele_straggler_scores{key="3",rank="0"} 0'
            in text)
