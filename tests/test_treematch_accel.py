"""topo/treematch reordering + accelerator framework widening
(streams, events, IPC, host register, device attrs)."""
import numpy as np
import pytest

import ompi_tpu as MPI
from ompi_tpu.accelerator import Event, Stream, current_module
from ompi_tpu.topo import treematch as tm


# -- treematch ---------------------------------------------------------
class _Dev:
    def __init__(self, i, coords, proc=0):
        self.id = i
        self.coords = coords
        self.process_index = proc
        self.platform = "fake"


def test_hardware_distance_manhattan_and_dcn():
    devs = [_Dev(0, (0, 0)), _Dev(1, (0, 1)), _Dev(2, (1, 0)),
            _Dev(3, (1, 1), proc=1)]
    d = tm.hardware_distance(devs)
    assert d[0, 1] == 1 and d[0, 2] == 1
    assert d[1, 2] == 2                       # (0,1)->(1,0)
    assert d[0, 3] == 2 + 8                   # cross-process penalty


def test_comm_matrix_from_graph():
    # ring of 4: index/edges in MPI_Graph_create format
    index = [2, 4, 6, 8]
    edges = [1, 3, 0, 2, 1, 3, 0, 2]
    m = tm.comm_matrix_from_graph(index, edges)
    assert m[0, 1] == 2 and m[0, 3] == 2 and m[0, 2] == 0


def test_treematch_improves_placement():
    """A chain graph 0-1-2-3 placed on a line where logical neighbors
    start physically far: treematch must beat identity cost."""
    devs = [_Dev(0, (0,)), _Dev(1, (3,)), _Dev(2, (1,)), _Dev(3, (2,))]
    hw = tm.hardware_distance(devs)
    cm = np.zeros((4, 4))
    for a, b in ((0, 1), (1, 2), (2, 3)):
        cm[a, b] = cm[b, a] = 10.0
    ident = tm.placement_cost(cm, hw)
    perm = tm.treematch_permutation(cm, hw)
    best = tm.placement_cost(cm, hw, perm)
    assert sorted(perm) == [0, 1, 2, 3]
    assert best < ident
    assert best == 10.0 * 3                   # chain on a line: optimal


def test_treematch_deterministic():
    devs = [_Dev(i, (i,)) for i in range(6)]
    hw = tm.hardware_distance(devs)
    cm = np.random.default_rng(0).random((6, 6))
    cm = cm + cm.T
    assert (tm.treematch_permutation(cm, hw)
            == tm.treematch_permutation(cm, hw))


def test_graph_create_reorder(world):
    """reorder=True rebinds ranks to devices; the topology itself is
    unchanged and collectives still work."""
    n = world.size
    index, edges = [], []
    for r in range(n):                        # ring
        edges += [(r - 1) % n, (r + 1) % n]
        index.append(len(edges))
    c = world.create_graph(index, edges, reorder=True)
    assert c.size == n
    assert c.graph_neighbors(0) == [n - 1, 1]
    assert sorted(d.id for d in c.devices) == \
        sorted(d.id for d in world.devices[:n])
    x = c.stack([np.full(3, r, np.float32) for r in range(n)])
    out = np.asarray(c.allreduce(x, MPI.SUM))
    assert out[0][0] == sum(range(n))


# -- accelerator widening ----------------------------------------------
def test_stream_ordering_and_sync(world):
    m = current_module()
    s = m.create_stream()
    assert isinstance(s, Stream) and s.depth == 0
    a = world.alloc((8,), np.float32, fill=1.0)
    b = world.allreduce(a, MPI.SUM)
    s.enqueue(a)
    s.enqueue(b)
    assert s.depth == 2
    s.sync()
    assert s.depth == 0


def test_event_record_query_synchronize(world):
    m = current_module()
    ev = m.create_event()
    assert isinstance(ev, Event)
    assert ev.query()                          # nothing recorded
    y = world.allreduce(world.alloc((4,), np.float32, fill=2.0), MPI.SUM)
    ev.record([y])
    ev.synchronize()
    assert ev.query()


def test_event_records_stream(world):
    m = current_module()
    s = m.create_stream()
    y = world.allreduce(world.alloc((4,), np.float32, fill=1.0), MPI.SUM)
    s.enqueue(y)
    ev = m.create_event()
    ev.record(s)
    ev.synchronize()
    assert ev.query()


def test_ipc_handles(world):
    m = current_module()
    buf = world.alloc((16,), np.float32, fill=3.0)
    h = m.get_ipc_handle(buf)
    assert m.open_ipc_handle(h) is buf
    m.close_ipc_handle(h)
    with pytest.raises(KeyError):
        m.open_ipc_handle(h)


def test_host_register_pins_and_protects():
    m = current_module()
    buf = np.arange(10, dtype=np.float32)
    m.host_register(buf)
    assert m.is_host_registered(buf)
    with pytest.raises(ValueError):
        buf[0] = 99.0                          # pinned = immutable
    m.host_unregister(buf)
    assert not m.is_host_registered(buf)
    buf[0] = 99.0                              # writable again


def test_host_register_refcounts():
    m = current_module()
    buf = np.arange(4, dtype=np.float32)
    m.host_register(buf)
    m.host_register(buf)               # double register
    m.host_unregister(buf)             # one unregister: still pinned
    assert m.is_host_registered(buf)
    assert not buf.flags.writeable
    m.host_unregister(buf)             # matched: restored
    assert not m.is_host_registered(buf)
    assert buf.flags.writeable


def test_host_register_restores_prior_state():
    m = current_module()
    ro = np.frombuffer(b"12345678", dtype=np.uint8)   # born read-only
    m.host_register(ro)
    m.host_unregister(ro)                              # must not raise
    assert not m.is_host_registered(ro)
    assert not ro.flags.writeable                      # still read-only


def test_message_queue_dst_filter(world):
    from ompi_tpu.tools import debuggers
    c = world.dup()
    c.irecv(source=1, tag=5, dst=0)
    c.irecv(source=2, tag=6, dst=3)
    q = debuggers.message_queues(c, dst=3)
    assert len(q["posted"]) == 1 and q["posted"][0]["tag"] == 6
    c.send(np.ones(1, np.float32), src=1, dest=0, tag=5)
    c.send(np.ones(1, np.float32), src=2, dest=3, tag=6)


def test_device_attributes_and_peers(world):
    m = current_module()
    attrs = m.get_device_attributes(world.devices[0])
    assert attrs["platform"] and "coords" in attrs
    assert m.device_can_access_peer(world.devices[0], world.devices[1])


def test_mem_alloc(world):
    m = current_module()
    z = m.mem_alloc((4, 4), np.float32)
    assert z.shape == (4, 4) and float(np.asarray(z).sum()) == 0.0
