"""Persistent collectives (coll/persistent): pre-bound plans match the
unfused one-shot path bit-for-bit, Start is launch-only (pvar-counted),
and the request state machine keeps MPI_Start/MPI_Request_free
semantics (ERR_REQUEST on active start, deferred free)."""
import numpy as np
import pytest

import ompi_tpu as MPI
from ompi_tpu.core.errhandler import ERR_REQUEST
from ompi_tpu.mca import pvar


def _stacked(world, shape, seed=0):
    """Integer-valued f32 stacked buffer: any combine order is exact,
    so parity assertions can be byte-identical."""
    rng = np.random.default_rng(seed)
    x = rng.integers(-8, 8, size=(world.size,) + shape).astype(np.float32)
    return x, world.stack(list(x))


# -- parity pairs (tools/checkparity contract: one per plan func) ----------
def test_persistent_allreduce_matches_unfused(world):
    x, buf = _stacked(world, (32,))
    ref = np.asarray(world.allreduce(buf, MPI.SUM))
    req = world.allreduce_init(buf, MPI.SUM)
    for _ in range(3):                   # re-armable: start/wait cycles
        req.start()
        req.wait()
    got = np.asarray(req.get())
    assert got.tobytes() == ref.tobytes()


def test_persistent_bcast_matches_unfused(world):
    x, buf = _stacked(world, (16,), seed=1)
    ref = np.asarray(world.bcast(buf, 0))
    req = world.bcast_init(buf, 0)
    req.start()
    req.wait()
    assert np.asarray(req.get()).tobytes() == ref.tobytes()


def test_persistent_allgather_matches_unfused(world):
    x, buf = _stacked(world, (8,), seed=2)
    ref = np.asarray(world.allgather(buf))
    req = world.allgather_init(buf)
    req.start()
    req.wait()
    assert np.asarray(req.get()).tobytes() == ref.tobytes()


def test_persistent_reduce_scatter_block_matches_unfused(world):
    n = world.size
    x, buf = _stacked(world, (n * 4,), seed=3)
    ref = np.asarray(world.reduce_scatter_block(buf, MPI.SUM))
    req = world.reduce_scatter_block_init(buf, MPI.SUM)
    req.start()
    req.wait()
    assert np.asarray(req.get()).tobytes() == ref.tobytes()


def test_persistent_barrier_matches_unfused(world):
    req = world.barrier_init()
    for _ in range(2):
        req.start()
        st = req.wait()
    assert st is not None
    ok, _ = req.test()
    assert ok


# -- Start is launch-only and counted --------------------------------------
def test_persistent_start_counts_pvar(world):
    _x, buf = _stacked(world, (4,), seed=4)
    req = world.allreduce_init(buf, MPI.SUM)
    before = pvar.pvar_read("coll_persistent_starts")
    for _ in range(5):
        req.start()
        req.wait()
    assert pvar.pvar_read("coll_persistent_starts") - before == 5


def test_persistent_plan_metadata(world):
    """The plan records what was decided at init: algorithm from the
    decision layer, codec only when the compress gates pass (off by
    default)."""
    _x, buf = _stacked(world, (64,), seed=5)
    req = world.allreduce_init(buf, MPI.SUM)
    assert req.plan.func == "allreduce"
    assert req.plan.algorithm
    assert req.plan.codec is None        # mpi_base_compress off


# -- request state machine (MPI_Start / MPI_Request_free semantics) --------
def _active_persistent():
    """A persistent request whose inner op completes only on demand."""
    g = MPI.Grequest()
    return MPI.Request(persistent_start=lambda: g), g


def test_start_on_nonpersistent_raises():
    r = MPI.Request.completed("x")
    with pytest.raises(MPI.MPIError) as ei:
        r.start()
    assert ei.value.error_class == ERR_REQUEST


def test_start_on_active_persistent_raises():
    req, g = _active_persistent()
    req.start()
    with pytest.raises(MPI.MPIError) as ei:
        req.start()
    assert ei.value.error_class == ERR_REQUEST
    g.complete(1)
    req.wait()
    req.start()                          # inactive again: re-armable
    req.wait()


def test_request_free_on_active_is_deferred():
    req, g = _active_persistent()
    req.start()
    req.free()
    assert req._free_pending and not req._freed
    with pytest.raises(MPI.MPIError):    # unusable from the free on
        req.start()
    g.complete(2)
    req.wait()                           # completion finishes the free
    assert req._freed and not req._free_pending
    with pytest.raises(MPI.MPIError):
        req.start()


def test_request_free_inactive_is_immediate():
    req, _g = _active_persistent()
    req.free()
    assert req._freed
    with pytest.raises(MPI.MPIError):
        req.start()


def test_persistent_coll_start_on_active_raises(world):
    """Same contract through the real persistent-collective request:
    completing via wait re-arms; a second start before completion is
    ERR_REQUEST. (The stacked plan's launch may complete fast, so the
    active window is forced through the inner-request hook.)"""
    _x, buf = _stacked(world, (4,), seed=6)
    req = world.allreduce_init(buf, MPI.SUM)
    req.start()
    # force the active-incomplete state regardless of device timing
    req._complete = False
    req._inner_req = MPI.Grequest()
    with pytest.raises(MPI.MPIError):
        req.start()
    req._inner_req.complete(None)
    req.wait()
    req.start()
    req.wait()
