"""BucketFuser (coll/persistent): small-collective fusion semantics —
off = byte-identical unfused dispatch, on = fused wire collectives with
exact results, Startall's wire-collective budget (pvar-asserted),
flush-reason trace spans aggregated by tracedump summary, compression
composition, decision-table gate rows, and the DDP gradient sync."""
import math

import numpy as np
import pytest

import ompi_tpu as MPI
from ompi_tpu.coll import persistent
from ompi_tpu.mca import pvar, var


@pytest.fixture()
def bucket(world):
    """Bucketing ON with a small threshold; always restored (and the
    world's fuser drained) so no other test sees fusion."""
    var.var_set("mpi_base_bucket", True)
    var.var_set("mpi_base_bucket_bytes", 1 << 14)
    try:
        yield world
    finally:
        persistent.flush_all("explicit")
        var.var_set("mpi_base_bucket_bytes", persistent.DEFAULT_BUCKET_BYTES)
        var.var_set("mpi_base_bucket", False)


def _bufs(world, k, elems, seed=0):
    rng = np.random.default_rng(seed)
    xs = [rng.integers(-8, 8, size=(world.size, elems)).astype(np.float32)
          for _ in range(k)]
    return [world.stack(list(x)) for x in xs]


# -- parity (tools/checkparity contract) -----------------------------------
def test_bucketed_allreduce_matches_unfused(world):
    bufs = _bufs(world, 6, 256)
    refs = [np.asarray(world.allreduce(b, MPI.SUM)) for b in bufs]

    var.var_set("mpi_base_bucket", True)
    var.var_set("mpi_base_bucket_bytes", 1 << 20)
    try:
        reqs = [world.allreduce_init(b, MPI.SUM) for b in bufs]
        MPI.Startall(reqs)
        for rq, ref in zip(reqs, refs):
            rq.wait()
            got = np.asarray(rq.get())
            # integer-valued f32: the fused elementwise combine is
            # exact, so fused == unfused bit-for-bit
            assert got.tobytes() == ref.tobytes()
    finally:
        persistent.flush_all("explicit")
        var.var_set("mpi_base_bucket_bytes", persistent.DEFAULT_BUCKET_BYTES)
        var.var_set("mpi_base_bucket", False)


def test_bucket_off_is_byte_identical(world):
    """The acceptance contract: with mpi_base_bucket off (the default)
    every path — blocking, one-shot nonblocking, persistent — returns
    the byte-identical unfused result."""
    assert not persistent.bucket_enabled()
    (buf,) = _bufs(world, 1, 128, seed=1)
    ref = np.asarray(world.allreduce(buf, MPI.SUM))
    i_res = np.asarray(world.iallreduce(buf, MPI.SUM).get())
    req = world.allreduce_init(buf, MPI.SUM)
    req.start()
    req.wait()
    p_res = np.asarray(req.get())
    assert i_res.tobytes() == ref.tobytes()
    assert p_res.tobytes() == ref.tobytes()


# -- Startall wire-collective budget (pvar-asserted) -----------------------
def test_startall_wire_collective_budget(bucket):
    world = bucket
    k, elems = 8, 1024                  # 4 KiB per rank per member
    member_bytes = elems * 4
    bucket_bytes = persistent.bucket_bytes()
    assert bucket_bytes == 1 << 14      # 4 members per bucket
    bufs = _bufs(world, k, elems, seed=2)
    refs = []
    var.var_set("mpi_base_bucket", False)
    for b in bufs:
        refs.append(np.asarray(world.allreduce(b, MPI.SUM)))
    var.var_set("mpi_base_bucket", True)

    f0 = pvar.pvar_read("coll_bucket_flushes")
    m0 = pvar.pvar_read("coll_bucket_fused_members")
    reqs = [world.allreduce_init(b, MPI.SUM) for b in bufs]
    MPI.Startall(reqs)
    for rq, ref in zip(reqs, refs):
        rq.wait()
        assert np.asarray(rq.get()).tobytes() == ref.tobytes()
    flushes = pvar.pvar_read("coll_bucket_flushes") - f0
    budget = math.ceil(k * member_bytes / bucket_bytes)
    assert flushes <= budget, (flushes, budget)
    assert pvar.pvar_read("coll_bucket_fused_members") - m0 == k
    # reason attribution: threshold flushes + at most one startall tail
    assert pvar.pvar_read("coll_bucket_flush_bytes") >= 1


def test_oneshot_iallreduce_fuses(bucket):
    world = bucket
    bufs = _bufs(world, 3, 64, seed=3)
    var.var_set("mpi_base_bucket", False)
    refs = [np.asarray(world.allreduce(b, MPI.SUM)) for b in bufs]
    var.var_set("mpi_base_bucket", True)
    f0 = pvar.pvar_read("coll_bucket_flushes")
    reqs = [world.iallreduce(b, MPI.SUM) for b in bufs]
    outs = [np.asarray(r.get()) for r in reqs]
    for got, ref in zip(outs, refs):
        assert got.tobytes() == ref.tobytes()
    # all three rode fused launches, not three separate wire colls
    assert pvar.pvar_read("coll_bucket_flushes") - f0 <= 2


def test_bucket_occupancy_level_pvar(bucket):
    world = bucket
    (buf,) = _bufs(world, 1, 64, seed=4)
    req = world.allreduce_init(buf, MPI.SUM)
    req.start()
    occ = pvar.pvar_read("coll_bucket_occupancy")
    assert occ >= buf.nbytes // world.size or req._inner_req._complete
    req.wait()
    assert pvar.pvar_read("coll_bucket_occupancy") == 0


# -- trace spans + tracedump summary aggregation ---------------------------
def test_bucket_flush_spans_and_summary(bucket):
    from ompi_tpu import trace
    from ompi_tpu.tools import tracedump
    world = bucket
    bufs = _bufs(world, 4, 64, seed=5)
    trace.enable()
    trace.reset()
    try:
        reqs = [world.allreduce_init(b, MPI.SUM) for b in bufs]
        MPI.Startall(reqs)
        for rq in reqs:
            rq.wait()
        spans = [s for s in trace.span_dicts()
                 if s["name"] == "coll.bucket_flush"]
        assert spans, "no bucket_flush span recorded"
        reasons = {s["args"]["reason"] for s in spans}
        assert reasons <= {"bytes", "startall", "idle", "explicit"}
        assert "startall" in reasons or "bytes" in reasons
        assert all(s["args"]["members"] >= 1 for s in spans)
        summary = tracedump.render(trace.span_dicts(), {}, "summary")
        agg = summary.get("bucket_flush")
        assert agg, summary
        assert sum(e["flushes"] for e in agg.values()) == len(spans)
        assert sum(e["members"] for e in agg.values()) == 4
    finally:
        trace.reset()
        trace.disable()


# -- composition with compress/ (satellite) --------------------------------
def test_bucketed_compressed_parity_and_ratio(world, rng):
    """Buckets crossing mpi_base_compress_min_bytes ride the codec:
    members individually below the floor, fused payload above it —
    quant bytes move (ratio pvar-asserted) and every member's result
    stays within the codec's documented error model."""
    n = world.size
    k, elems = 16, 8192                 # 32 KiB/rank each, 512 KiB fused
    xs = [rng.normal(size=(n, elems)).astype(np.float32)
          for _ in range(k)]
    var.var_set("mpi_base_compress", True)
    var.var_set("mpi_base_compress_min_bytes", 256 << 10)
    var.var_set("mpi_base_bucket", True)
    var.var_set("mpi_base_bucket_bytes", 1 << 20)
    try:
        c = world.dup()                 # vtable selected with compress on
        bufs = [c.stack(list(x)) for x in xs]
        bi0 = pvar.pvar_read("compress_bytes_in")
        bo0 = pvar.pvar_read("compress_bytes_out")
        reqs = [c.allreduce_init(b, MPI.SUM) for b in bufs]
        MPI.Startall(reqs)
        outs = [np.asarray(r.get()) for r in reqs]
        bi1 = pvar.pvar_read("compress_bytes_in")
        bo1 = pvar.pvar_read("compress_bytes_out")
        assert bi1 > bi0, "fused bucket never engaged the codec"
        assert (bo1 - bo0) / (bi1 - bi0) <= 0.5, "no wire savings"
        for x, got in zip(xs, outs):
            ref = x.sum(axis=0, dtype=np.float64)
            err = np.abs(got[0].astype(np.float64) - ref).max()
            assert err <= 0.02 * np.abs(ref).max() + 1e-6
            for r in range(1, n):       # same value everywhere
                assert np.array_equal(got[0], got[r])
        c.free()
    finally:
        persistent.flush_all("explicit")
        var.var_set("mpi_base_bucket_bytes", persistent.DEFAULT_BUCKET_BYTES)
        var.var_set("mpi_base_bucket", False)
        var.var_set("mpi_base_compress_min_bytes", 4 << 20)
        var.var_set("mpi_base_compress", False)


# -- decision-table gate rows (satellite) ----------------------------------
def test_decision_table_persistent_and_bucket_rows(world):
    from ompi_tpu.api import tool
    t_off = tool.decision_table(comm_size=world.size, platform="cpu")
    # persistent prebound rows: always present, one per *_init func
    for func in persistent.PERSISTENT_FUNCS:
        assert t_off[f"{func}_init"] == [[0, 0, "persistent_prebound"]]
    # bucket rows: only while the var is on (the compression-row idiom)
    assert not any("bucket_fuse" in str(r[2])
                   for rules in t_off.values() for r in rules)
    var.var_set("mpi_base_bucket", True)
    try:
        t_on = tool.decision_table(comm_size=world.size, platform="cpu")
        rows = [r for r in t_on["allreduce"]
                if str(r[2]).startswith("bucket_fuse:")]
        assert rows and str(persistent.bucket_bytes()) in rows[-1][2]
    finally:
        var.var_set("mpi_base_bucket", False)


def test_checkparity_requires_persistent_pairs(tmp_path):
    """A tree with compress pairs but no persistent/fused pairs fails
    the audit with the missing names listed."""
    from ompi_tpu.tools import checkparity
    (tmp_path / "test_x.py").write_text(
        "def test_compressed_allreduce_matches_uncompressed():\n"
        "    pass\n"
        "def test_compressed_allgather_matches_uncompressed():\n"
        "    pass\n"
        "def test_compressed_reduce_scatter_block_matches_uncompressed"
        "():\n    pass\n")
    report = checkparity.audit(str(tmp_path))
    assert not report["ok"]
    assert "test_persistent_allreduce_matches_unfused" \
        in report["missing_persistent_parity"]
    assert "test_bucketed_allreduce_matches_unfused" \
        in report["missing_persistent_parity"]


# -- DDP gradient sync (models/transformer) --------------------------------
def test_bucketed_grad_sync_matches_per_leaf_allreduce(world):
    from ompi_tpu.models.transformer import BucketedGradSync
    n = world.size
    rng = np.random.default_rng(7)
    tree = {"w": world.stack(list(
                rng.integers(-4, 4, size=(n, 8, 8)).astype(np.float32))),
            "b": world.stack(list(
                rng.integers(-4, 4, size=(n, 8)).astype(np.float32)))}
    refs = {k: np.asarray(world.allreduce(v, MPI.SUM)) / n
            for k, v in tree.items()}
    var.var_set("mpi_base_bucket", True)
    try:
        sync = BucketedGradSync(world, tree)
        out = sync(tree)
        for k in tree:
            assert np.allclose(np.asarray(out[k]), refs[k])
        loss = sync.mean_scalar(2.5)
        assert np.allclose(np.asarray(loss), 2.5)
    finally:
        persistent.flush_all("explicit")
        var.var_set("mpi_base_bucket", False)
