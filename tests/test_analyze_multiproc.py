"""Slow-tier lockwitness drill (the ISSUE-10 acceptance run): a REAL
4-rank per-rank job with pt2pt sends, persistent collectives, and ft
heartbeats concurrent under ``mpi_base_lockwitness=1``; every rank
asserts its acquisition-order graph is acyclic, and the per-rank
graph dumps merge through ``tools/tracedump summary``."""
import glob
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PROGS = os.path.join(_REPO, "tests", "perrank_programs")
_MPIRUN = os.path.join(_REPO, "ompi_tpu", "tools", "mpirun.py")


def test_lockwitness_drill_acyclic(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["P40_DUMP_DIR"] = str(tmp_path)
    cmd = [sys.executable, _MPIRUN, "--per-rank", "-n", "4",
           "--timeout", "150",
           os.path.join(_PROGS, "p40_lockwitness.py")]
    res = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=200, cwd=_REPO)
    assert res.returncode == 0, \
        f"rc={res.returncode}\n--- out\n{res.stdout}\n" \
        f"--- err\n{res.stderr[-4000:]}"
    assert res.stdout.count("OK p40_lockwitness") == 4, res.stdout

    files = sorted(glob.glob(os.path.join(str(tmp_path), "lw_r*.json")))
    assert len(files) == 4, files

    # the documented merge surface: tracedump summary over the dumps
    from ompi_tpu.tools import tracedump
    out = tmp_path / "summary.json"
    assert tracedump.main(["--format", "summary",
                           "-o", str(out), *files]) == 0
    lwsec = json.loads(out.read_text())["lockwitness"]
    assert lwsec["ranks"] == 4
    assert lwsec["edges"], "drill observed no lock nesting at all"
    # the acceptance assertion: the 4-rank concurrent workload's merged
    # acquisition-order graph is ACYCLIC
    assert lwsec["cycles"] == [], json.dumps(lwsec["cycles"], indent=1)
    assert lwsec["per_rank_cycles"] == {}
    assert lwsec["max_hold_us"] > 0.0
